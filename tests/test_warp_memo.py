"""Warp 3.0 — signature-keyed span memoization + distributional mode.

The memo's contract is replay-equals-dispatch: with ``warp_mode="exact"``
a memoized run is bit-identical to the same run without a memo (which is
itself bit-identical to dense ticking — tests/test_warp.py), every hit
skipping its dispatch outright. The cache is bounded (bytes AND entries,
LRU), keyed by (kind, family, span length, signature class, entry-state
digest), and one lane's banked delta is a hit for every other fleet
member / serve lane entering the same state. ``distributional`` mode is
the explicit non-bit-exact tier: randomized drain schedules pin it on
distribution statistics — convergence-tick band, steady-state message
means, final membership planes — never on bits.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.runner import simulate, state_converged
from kaboodle_tpu.sim.scenario import Scenario
from kaboodle_tpu.sim.state import idle_inputs, init_state
from kaboodle_tpu.warp.runner import (
    SpanMemo,
    WarpLedger,
    _get_fleet_leap,
    memo_fleet_leap,
    run_fleet_warped,
    run_warped,
    simulate_warped,
)


def _assert_leaves_equal(tree_a, tree_b, ctx=""):
    for a, b in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
        av, bv = np.asarray(a), np.asarray(b)
        if av.dtype == np.float32:
            assert ((av == bv) | (np.isnan(av) & np.isnan(bv))).all(), ctx
        else:
            assert (av == bv).all(), (ctx, av.dtype)


def _converged_init(n, seed=0, **kw):
    return init_state(n, seed=seed, ring_contacts=n - 1, announced=True, **kw)


def _churn_scenario(n, T, seed=0):
    return (Scenario(n, T, seed=seed)
            .kill_at(8, [3])
            .manual_ping_at(20, 0, 2)
            .kill_at(32, [7])
            .revive_at(44, [3]))


# ---------------------------------------------------------------------------
# SpanMemo unit contract: bounded, LRU, keyed


def test_span_memo_bounded_entries_lru_eviction():
    memo = SpanMemo(max_bytes=1 << 20, max_entries=3)
    for key in ("a", "b", "c"):
        memo.put(key, (key,), 10)
    assert memo.stats()["entries"] == 3
    assert memo.get("a") == ("a",)  # refresh: "b" is now least-recent
    memo.put("d", ("d",), 10)
    s = memo.stats()
    assert s["entries"] == 3 and s["evictions"] == 1
    assert memo.get("b") is None  # the LRU victim
    assert memo.get("a") == ("a",) and memo.get("d") == ("d",)


def test_span_memo_bounded_bytes():
    memo = SpanMemo(max_bytes=100, max_entries=64)
    memo.put("big", ("x",), 200)  # over the whole budget: never stored
    assert memo.stats()["entries"] == 0
    assert memo.get("big") is None
    memo.put("a", ("a",), 60)
    memo.put("b", ("b",), 60)  # 120 > 100: "a" evicted
    s = memo.stats()
    assert s["entries"] == 1 and s["bytes"] == 60 and s["evictions"] == 1
    assert memo.get("a") is None and memo.get("b") == ("b",)


def test_span_memo_per_kind_stats():
    memo = SpanMemo()
    memo.put(("k", 1), ("v",), 8)
    assert memo.get(("k", 1), kind="leap") == ("v",)
    assert memo.get(("nope",), kind="dense") is None
    per = memo.stats()["per_kind"]
    assert per["leap"] == {"hits": 1, "misses": 0, "hit_rate": 1.0}
    assert per["dense"] == {"hits": 0, "misses": 1, "hit_rate": 0.0}
    memo.clear()
    assert memo.stats()["entries"] == 0 and memo.stats()["hits"] == 0


# ---------------------------------------------------------------------------
# replay == dispatch, bit for bit


def test_simulate_warped_memo_bit_exact_and_hits():
    n, T = 24, 64
    cfg = SwimConfig()
    st = _converged_init(n, seed=1)
    inp = _churn_scenario(n, T).build()
    ref, ref_ticks, ref_m = simulate_warped(st, inp, cfg, faulty=True,
                                            recheck_every=4)
    memo = SpanMemo()
    for run in range(2):
        ledger = WarpLedger()
        w, ticks, m = simulate_warped(st, inp, cfg, faulty=True,
                                      recheck_every=4, memo=memo,
                                      ledger=ledger)
        _assert_leaves_equal(ref, w, f"memo run {run}")
        assert list(ref_ticks) == list(ticks)
        if ref_m is not None:
            _assert_leaves_equal(ref_m, m, f"memo metrics {run}")
        if run == 1:
            # Every span replays: all engines in the ledger are +memo rows
            # with zero dispatches — the why-dense histogram shrank to
            # exactly the replayed rows.
            assert all(r["engine"].endswith("+memo") for r in ledger.spans)
            assert all(r["dispatches"] == 0 for r in ledger.spans)
    s = memo.stats()
    assert s["hits"] > 0 and s["hits"] == s["misses"]  # pass 2 is all hits


def test_run_warped_memo_bit_exact_and_hits():
    n, ticks = 24, 40
    cfg = SwimConfig()
    st = _converged_init(n, seed=2)
    ref, rt, rc = run_warped(st, cfg, ticks, recheck_every=4)
    memo = SpanMemo()
    for _ in range(2):
        w, wt, wc = run_warped(st, cfg, ticks, recheck_every=4, memo=memo)
        _assert_leaves_equal(ref, w, "run_warped memo")
        assert int(rt) == int(wt) and bool(rc) == bool(wc)
    assert memo.stats()["hits"] > 0


# ---------------------------------------------------------------------------
# cross-lane / cross-member hits


def test_memo_fleet_leap_cross_member_hit_parity():
    """A delta banked from a 2-member dispatch replays for a 4-member
    fleet whose lanes enter the same state — all-hit, dispatch skipped,
    bit-identical with the dispatched program."""
    n, k = 16, 8
    cfg = SwimConfig()
    member = _converged_init(n, seed=0)
    stack2 = jax.tree.map(lambda *xs: jnp.stack(xs), member, member)
    stack4 = jax.tree.map(lambda *xs: jnp.stack(xs), member, member,
                          member, member)
    prog = _get_fleet_leap(cfg, k)
    memo = SpanMemo()
    k2 = np.full((2,), k, np.int32)
    out2, hits2, disp2 = memo_fleet_leap("fam", stack2, k2, memo, prog)
    assert disp2 and hits2 == 0
    _assert_leaves_equal(prog(stack2, jnp.asarray(k2)), out2, "dispatch leg")
    k4 = np.full((4,), k, np.int32)
    out4, hits4, disp4 = memo_fleet_leap("fam", stack4, k4, memo, prog)
    assert not disp4 and hits4 == 4  # every lane replayed another's delta
    _assert_leaves_equal(prog(stack4, jnp.asarray(k4)), out4, "replay leg")
    assert memo.stats()["per_kind"]["fleet"]["hits"] == 4


def test_run_fleet_warped_memo_bit_exact():
    from kaboodle_tpu.fleet.core import init_fleet

    n, e, ticks = 16, 4, 20
    cfg = SwimConfig()
    fleet = init_fleet(n, e, ring_contacts=n - 1, announced=True)
    ref, rt, rc = run_fleet_warped(fleet, cfg, ticks)
    memo = SpanMemo()
    for _ in range(2):
        out, t, c = run_fleet_warped(fleet, cfg, ticks, memo=memo)
        _assert_leaves_equal(ref.mesh, out.mesh, "fleet memo")
        assert int(rt) == int(t)
        assert (np.asarray(rc) == np.asarray(c)).all()
    assert memo.stats()["hits"] > 0


# ---------------------------------------------------------------------------
# distributional mode: randomized drain schedules, distribution-pinned


def _trailing_converged_start(conv):
    """First tick index of the trailing all-True convergence run (len(conv)
    when the run is empty — never converged at the end)."""
    conv = np.asarray(conv, dtype=bool)
    idx = len(conv)
    while idx > 0 and conv[idx - 1]:
        idx -= 1
    return idx


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_distributional_randomized_drain_pinned_on_distribution(seed):
    """Randomized kills/revive in the first half, quiet second half: the
    distributional run must land the same membership planes, converge
    within a band of the dense convergence tick, and match the dense
    steady state's per-tick message mean — the explicit contract for the
    one mode that is NOT bit-exact."""
    rng = np.random.default_rng(seed)
    n, T = 24, 96
    cfg = SwimConfig()
    st = _converged_init(n, seed=seed)
    victims = rng.choice(np.arange(1, n), size=2, replace=False)
    sc = (Scenario(n, T, seed=seed)
          .kill_at(int(rng.integers(4, 16)), [int(victims[0])])
          .kill_at(int(rng.integers(20, 32)), [int(victims[1])])
          .revive_at(int(rng.integers(40, 48)), [int(victims[0])]))
    inp = sc.build()

    dense_final, dense_m = simulate(st, inp, cfg, faulty=True)
    dense_conv = _trailing_converged_start(dense_m.converged)
    assert dense_conv < T, "schedule must settle densely"

    samples = []  # (tick, converged) at every horizon boundary
    warped, dense_ticks, warped_m = simulate_warped(
        st, inp, cfg, faulty=True, recheck_every=4,
        warp_mode="distributional",
        on_boundary=lambda t, s: samples.append((t, bool(state_converged(s)))),
    )
    if warped_m is not None:
        for j, t in enumerate(dense_ticks):
            row = jax.tree.map(lambda x: x[j], warped_m)
            samples.append((int(t) + 1, bool(np.asarray(row.converged))))
    samples.sort()

    # Membership planes agree exactly: kills/revives are schedule-driven
    # and the budget clip keeps expiry ticks dense in every mode.
    assert (np.asarray(warped.alive) == np.asarray(dense_final.alive)).all()
    assert bool(state_converged(warped))

    # Convergence-tick band: distributional may shift arrival ticks, not
    # lose convergence — the sampled trailing-True run must start within
    # a recheck quantum + one leap bucket of the dense tick.
    ticks_s = [t for t, _ in samples]
    conv_s = [c for _, c in samples]
    dist_conv = ticks_s[_trailing_converged_start(conv_s)] \
        if _trailing_converged_start(conv_s) < len(conv_s) else T
    assert dist_conv <= dense_conv + 16, (dist_conv, dense_conv)

    # Steady counter means: 8 post-schedule idle ticks from each final
    # state deliver the same per-tick message mean (n_alive pings + acks
    # — a distribution statistic that survives arrival-tick shifts).
    tick = jax.jit(make_tick_fn(cfg, faulty=True))
    idle = idle_inputs(n)

    def steady_mean(state):
        msgs = []
        for _ in range(8):
            state, m = tick(state, idle)
            msgs.append(int(np.asarray(m.messages_delivered)))
        return np.mean(msgs)

    assert steady_mean(dense_final) == steady_mean(warped)


# ---------------------------------------------------------------------------
# zero fresh compiles: memo replay and distributional both ride warm programs


def test_zero_fresh_compiles_memo_and_distributional():
    from kaboodle_tpu.analysis.ir.surface import compile_counter

    n, T = 24, 64
    cfg = SwimConfig()
    st = _converged_init(n, seed=3)
    inp = _churn_scenario(n, T).build()
    memo = SpanMemo()
    # Warm pass: compiles the span/dense programs and banks every delta.
    simulate_warped(st, inp, cfg, faulty=True, recheck_every=4, memo=memo)
    simulate_warped(st, inp, cfg, faulty=True, recheck_every=4,
                    warp_mode="distributional")
    with compile_counter() as box:
        simulate_warped(st, inp, cfg, faulty=True, recheck_every=4,
                        memo=memo)
        simulate_warped(st, inp, cfg, faulty=True, recheck_every=4,
                        warp_mode="distributional")
    assert box.count == 0, (
        f"{box.count} fresh compiles in warmed memo-replay + "
        "distributional re-runs"
    )
